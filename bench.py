"""Headline benchmark: fault-injection throughput (injections/sec).

The reference's campaign loop (supervisor.py + QEMU + GDB) costs on the
order of seconds per injection: per-benchmark guest wall-clock alone is
bounded at 0.25-2.0 s (resources/benchmarks.py:27-73 maxSleepTime), plus
GDB round-trips and QEMU/GDB restarts (BASELINE.md "Injection throughput").
We take 1.0 injection/sec as the reference baseline -- the generous end of
that range -- and measure our batched XLA campaign on matrixMultiply under
TMR (BASELINE.json config 1).  North star: >= 1000x.

Prints ONE COMPACT JSON line (headline fields only: metric / value / unit /
vs_baseline / backend / flagship fraction-of-peak / artifact path).  The
full record -- per-batch throughput, overhead ratios, flagship arrays --
goes to artifacts/bench_full.json (always) and artifacts/last_tpu_bench.json
(when the backend is real hardware).  Round 3's single line grew to ~8 KB
and outran the driver's tail capture (BENCH_r03 parsed: null); bulk now
lives in artifacts/ only.

Robustness (VERDICT round 1 #1: BENCH_r01 was rc=1 with a bare traceback):
the measurement runs in a supervised *worker subprocess* with stage-level
progress records, because on this hardware the axon TPU backend can wedge
inside backend init (jax.devices() blocking on the device claim) or fail
at the first dispatch.  The parent watches the worker with bounded
timeouts, retries a fast failure once, falls back to the CPU backend when
the TPU is unreachable, and ALWAYS emits a machine-readable JSON line --
including an "error" field describing what the TPU did -- with rc=0
whenever any measurement exists.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

BASELINE_INJ_PER_SEC = 1.0  # QEMU+GDB loop, seconds-per-injection regime

# Published single-chip bf16 matmul peak for the chip this tunnel exposes
# (TPU v5e: 197 TFLOP/s bf16).  Flagship records report achieved FLOP/s as
# a fraction of this so the utilization story is explicit, not a bare
# GFLOP/s number.
TPU_V5E_BF16_PEAK_GFLOPS = 197_000.0

# Last-known-good on-chip measurement, refreshed whenever a TPU-backed run
# completes; embedded in the output when the tunnel is down so a CPU
# fallback record never silently replaces the hardware story.
LAST_TPU_RECORD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "artifacts", "last_tpu_bench.json")


def _backend_record_path(backend: str) -> str:
    """Per-backend last-known record: `artifacts/last_bench_<backend>`.
    CPU-fallback rounds compare against (and refresh) the CPU record,
    on-chip rounds the TPU one -- a fallback round can neither clobber
    nor be judged against the hardware trajectory."""
    safe = "".join(c if c.isalnum() else "_" for c in backend or "unknown")
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "artifacts", f"last_bench_{safe}.json")


def _load_backend_baseline(backend: str):
    try:
        with open(_backend_record_path(backend)) as f:
            rec = json.load(f)
        return {"value": rec.get("record", {}).get("value"),
                "measured_at": rec.get("measured_at")}
    except (OSError, ValueError):
        return None

# Stage timeouts (seconds), env-tunable for the driver.
INIT_TIMEOUT = int(os.environ.get("COAST_BENCH_INIT_TIMEOUT", "420"))
RETRY_TIMEOUT = int(os.environ.get("COAST_BENCH_RETRY_TIMEOUT", "180"))
RUN_TIMEOUT = int(os.environ.get("COAST_BENCH_RUN_TIMEOUT", "900"))
# Claim-contention retry loop: the axon tunnel exposes ONE device claim,
# and a wedged earlier worker (or a neighbour process) holding it makes
# every fresh attempt die in init.  A claim-like failure retries with
# exponential backoff instead of instantly burning the remaining plan
# entries against a device that may free up in seconds.  The loop is
# bounded BOTH by attempt count and by total wall clock
# (COAST_BENCH_CLAIM_TOTAL_S): ROADMAP notes whole bench rounds lost to
# spawn-wedge retry churn, so when the budget runs out the giving-up
# reason is ONE explicit line, not a pile of per-attempt stderr.
CLAIM_RETRIES = int(os.environ.get("COAST_BENCH_CLAIM_RETRIES", "2"))
CLAIM_BACKOFF_S = float(os.environ.get("COAST_BENCH_CLAIM_BACKOFF_S", "45"))
# Default budget fits the slowest claim-like failure (a full init-stage
# wedge) PLUS at least one backoff+retry cycle: a wedge that takes
# INIT_TIMEOUT to manifest must not exhaust the budget before the first
# retry the backoff loop exists to give it.
CLAIM_TOTAL_S = float(os.environ.get(
    "COAST_BENCH_CLAIM_TOTAL_S",
    str(INIT_TIMEOUT + RETRY_TIMEOUT + 2 * CLAIM_BACKOFF_S)))
# The toy campaign's replica state is KiB-scale, so batch is bounded by
# dispatch amortization, not HBM: the 2026-08-01 on-chip capture scaled
# near-linearly 1024 -> 4096 (14k -> 54k inj/s), so the sweep extends
# until the curve bends.
BATCHES = (1024, 2048, 4096, 8192, 16384, 32768, 65536, 131072)


# ---------------------------------------------------------------------------
# Worker: one backend attempt.  Emits one JSON record per line on stdout:
#   {"stage": "init", ...}   backend is up (devices visible)
#   {"stage": "dispatch"}    first op executed
#   {"stage": "result", ...} a finished measurement (possibly several)
#   {"stage": "done"}        all measurements finished
# The parent treats the last "result" as authoritative, so a wedge mid-way
# still yields partial numbers.
# ---------------------------------------------------------------------------

def _emit(rec):
    sys.stdout.write(json.dumps(rec) + "\n")
    sys.stdout.flush()
    try:
        from coast_tpu.obs import flightrec
        flightrec.record("spawn_stage", stage=rec.get("stage"),
                         kind=rec.get("kind"))
    except Exception:  # noqa: BLE001 - progress lines must never die
        pass


def worker(backend: str) -> None:
    # First breath before ANY heavy import: the parent's wedge forensics
    # hinge on whether this line arrives.  Spawn line seen + no init line
    # == the wedge is inside jax/PJRT backend init (the device claim);
    # NOT even this line == the wedge is interpreter startup itself (the
    # site hook importing the axon plugin), which no amount of in-worker
    # instrumentation can witness.
    _emit({"stage": "spawn", "pid": os.getpid()})
    # Blackbox next, backend after: the stage the recorder most needs
    # to witness is the init wedge, which happens inside the very next
    # import.  The parent points COAST_FLIGHTREC_DIR at its harvest
    # directory and SIGUSR1s us for the bundle before it kills us.
    from coast_tpu.obs import flightrec
    rec = flightrec.install(source=f"bench-worker:{backend}")
    rec.install_signal_handler()
    if backend == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    if backend == "cpu":
        # The axon site hook registers its PJRT plugin programmatically, so
        # the env var alone is not sufficient (see tests/conftest.py).
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    devs = jax.devices()
    _emit({"stage": "init", "backend": jax.default_backend(),
           "devices": [str(d) for d in devs]})

    jnp.add(jnp.int32(1), jnp.int32(1)).block_until_ready()
    _emit({"stage": "dispatch"})

    from coast_tpu import DWC, TMR, unprotected
    from coast_tpu.inject.campaign import CampaignRunner
    from coast_tpu.models import REGISTRY

    region = REGISTRY["matrixMultiply"]()

    # -- protected-vs-unprotected runtime overhead (the MWTF denominator,
    #    jsonParser.py:458-506) -------------------------------------------
    # Per-variant cost is the MEDIAN of several short timed blocks with the
    # variants interleaved: a single long block per variant confounds the
    # measurement with tunnel-latency drift (a recorded artifact once
    # showed TMR 3x FASTER than unprotected -- physically impossible for
    # triplicated work, pure drift).
    # Single-run timings arm a never-firing fault as a traced input so
    # XLA cannot fold the zero-arg computation (ops.bitflip.noop_fault).
    from coast_tpu.ops.bitflip import noop_fault as _noop
    noop_fault = _noop()
    runs = {}
    for name, make in (("unprotected", unprotected), ("DWC", DWC),
                       ("TMR", TMR)):
        jit_run = jax.jit(lambda f, p=make(region): p.run(f))
        jax.block_until_ready(jit_run(noop_fault))      # compile
        runs[name] = (lambda r=jit_run: r(noop_fault))
    blocks = {name: [] for name in runs}
    for _ in range(5):
        for name, run in runs.items():
            t0 = time.perf_counter()
            for _ in range(4):
                out = run()
            jax.block_until_ready(out)
            blocks[name].append((time.perf_counter() - t0) / 4)
    overhead = {name: sorted(b)[len(b) // 2] for name, b in blocks.items()}
    rec = {"stage": "result", "kind": "overhead",
           "seconds_per_run": {k: round(v, 6) for k, v in overhead.items()},
           "tmr_runtime_x": round(overhead["TMR"] / overhead["unprotected"], 3),
           "dwc_runtime_x": round(overhead["DWC"] / overhead["unprotected"], 3)}
    if rec["tmr_runtime_x"] < 1.0 or rec["dwc_runtime_x"] < 1.0:
        rec["noise_note"] = ("protected variant measured faster than "
                             "unprotected: dispatch-bound guest, ratios "
                             "within tunnel-latency noise")
    _emit(rec)

    # -- injections/sec on mm-TMR at several batch sizes -------------------
    # COAST_BENCH_UNROLL: early-exit loop steps per iteration
    # (classification-identical; the on-chip sweep in scripts/mfu_sweep.py
    # prices the trade for dispatch-bound tiny-benchmark campaigns).
    unroll = max(1, int(os.environ.get("COAST_BENCH_UNROLL", "1")))
    # profile=True: every round artifact records the measured device-busy
    # fraction AND the resolved backend per throughput row, so a
    # CPU-fallback round (the PR 6-10 unmeasured-on-chip gap) is
    # self-identifying instead of silently comparable to on-chip rows.
    runner = CampaignRunner(TMR(region), strategy_name="TMR",
                            unroll=unroll, profile=True)
    best = None
    for batch in BATCHES:
        runner.run(batch, seed=1, batch_size=batch)          # compile+warm
        res = runner.run(4 * batch, seed=42, batch_size=batch)
        prof = res.profile or {}
        rec = {"stage": "result", "kind": "throughput",
               "benchmark": "matrixMultiply", "strategy": "TMR",
               "backend": jax.default_backend(),
               "batch_size": batch, "injections": res.n,
               "seconds": round(res.seconds, 4),
               "injections_per_sec": round(res.injections_per_sec, 2),
               "device_busy_fraction": prof.get("device_busy_fraction"),
               "dispatch_gap_fraction": prof.get("dispatch_gap_fraction"),
               "counts": res.counts}
        _emit(rec)
        if best is None or res.injections_per_sec > best:
            best = res.injections_per_sec

    # -- TPU-shaped flagships: mm256 (1 MiB f32) and mm1024 (4 MiB bf16
    # MXU).  Reports achieved FLOP/s and HBM-resident replica bytes
    # alongside injections/sec: the utilization evidence behind the
    # "TPU-native" claim (a 9x9 guest kernel cannot exercise the
    # hardware).  Batches are capped well below the toy benchmark's: each
    # campaign holds MiBs of replica state, and oversized batches fall
    # off an HBM cliff (measured: mm256 batch 1024 -> 18 inj/s vs 256 ->
    # 280 inj/s on v5e-lite).  Skipped whenever the RESOLVED backend is
    # CPU (the explicit fallback attempt, or a "default" attempt that
    # silently landed on the host): the flagships exist to measure the
    # hardware, and their MiB-scale campaigns would eat the whole run
    # window on a host core.
    flagships = (() if jax.default_backend() == "cpu" else
                 (("matrixMultiply256", (256, 512)),
                  ("matrixMultiply1024", (32, 64)),
                  # block=512 variant: the high-MFU roofline row
                  # (docs/perf.md) -- 4x less voter HBM per run.
                  ("matrixMultiply1024b512", (32, 64))))
    for flag_name, batches in flagships:
        flag = REGISTRY[flag_name]()
        # Flagships ship with the fused Pallas voter kernel
        # (bit-identical to the jnp voter; ~2x mm256's single-run rate).
        fl_prog = TMR(flag, pallas_voters=True)
        fl_jit = jax.jit(lambda f, p=fl_prog: p.run(f))
        fl_run = lambda: fl_jit(noop_fault)      # noqa: E731
        jax.block_until_ready(fl_run())
        reps = 10
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fl_run()
        jax.block_until_ready(out)
        sec_per_run = (time.perf_counter() - t0) / reps
        lanes_flops = 3 * flag.meta["flops_per_run"]
        gflops = lanes_flops / sec_per_run / 1e9
        fl_rec = {"stage": "result", "kind": "flagship",
                  "benchmark": flag_name, "strategy": "TMR",
                  "state_bytes": flag.meta["state_bytes"],
                  "seconds_per_run": round(sec_per_run, 6),
                  "gflops_per_sec": round(gflops, 2),
                  "fraction_of_peak": round(
                      gflops / TPU_V5E_BF16_PEAK_GFLOPS, 5),
                  "peak_ref": "v5e bf16 197 TFLOP/s"}
        fl_runner = CampaignRunner(fl_prog, strategy_name="TMR",
                                   profile=True)
        fl_batches = []
        for batch in batches:
            fl_runner.run(batch, seed=1, batch_size=batch)   # compile+warm
            res = fl_runner.run(2 * batch, seed=42, batch_size=batch)
            camp_gflops = lanes_flops * res.n / res.seconds / 1e9
            fl_prof = res.profile or {}
            fl_batches.append({
                "batch_size": batch, "injections": res.n,
                "backend": jax.default_backend(),
                "seconds": round(res.seconds, 4),
                "injections_per_sec": round(res.injections_per_sec, 2),
                "gflops_per_sec": round(camp_gflops, 2),
                "fraction_of_peak": round(
                    camp_gflops / TPU_V5E_BF16_PEAK_GFLOPS, 5),
                "device_busy_fraction":
                    fl_prof.get("device_busy_fraction"),
                "dispatch_gap_fraction":
                    fl_prof.get("dispatch_gap_fraction"),
                "counts": res.counts})
        fl_rec["campaign"] = fl_batches
        _emit(fl_rec)

    _emit({"stage": "done", "best_injections_per_sec": round(best, 2)})


# ---------------------------------------------------------------------------
# Parent: supervise attempts, always emit the one JSON line.
# ---------------------------------------------------------------------------

def _note(msg: str) -> None:
    """Spawn-stage progress reporting: one stderr line per supervision
    event, so a tail of the poller log shows WHERE an attempt is (spawn /
    init / dispatch / result...) instead of minutes of silence."""
    print(f"# bench {time.strftime('%H:%M:%S')} {msg}", file=sys.stderr,
          flush=True)


def _iter_own_workers():
    """(pid, age_seconds) of OTHER bench.py --worker processes we own.
    /proc scan (no psutil in the image); age from the stat starttime."""
    me = os.getpid()
    try:
        with open("/proc/uptime") as f:
            uptime = float(f.read().split()[0])
        hertz = os.sysconf("SC_CLK_TCK")
    except (OSError, ValueError):
        return
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) == me:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().decode(errors="replace").split("\0")
            if not ("--worker" in cmd
                    and any(c.endswith("bench.py") for c in cmd)):
                continue
            st = os.stat(f"/proc/{pid}")
            if st.st_uid != os.getuid():
                continue
            with open(f"/proc/{pid}/stat") as f:
                # Field 22 (1-indexed) = starttime in clock ticks; fields
                # 2 can contain spaces, so split after the comm paren.
                stat = f.read()
            start_ticks = int(stat.rsplit(")", 1)[1].split()[19])
            yield int(pid), uptime - start_ticks / hertz
        except (OSError, ValueError, IndexError):
            continue


def _kill_stale_workers(max_age_s: float) -> list:
    """Stale-own-process detection: a worker from a previous poller
    window that outlived every supervision budget is wedged inside
    backend init and HOLDS THE DEVICE CLAIM -- every new attempt then
    resolves to the CPU fallback.  Kill such leftovers before spawning;
    a live sibling younger than its own budgets is left alone."""
    killed = []
    for pid, age in _iter_own_workers() or ():
        if age > max_age_s:
            try:
                os.kill(pid, signal.SIGKILL)
                killed.append(pid)
                _note(f"killed stale worker pid {pid} (age {age:.0f}s > "
                      f"{max_age_s:.0f}s budget)")
            except OSError:
                pass
    return killed


def _probe_env() -> dict:
    """Pre-spawn environment probe: everything the wedge diagnosis needs,
    gathered WITHOUT importing jax in-process (importing it is exactly
    the operation that wedges).  Cheap filesystem facts only:

    - ``device_nodes``: TPU device files (``/dev/accel*``, ``/dev/vfio``)
      -- absent means there is no chip behind this container and the
      PJRT plugin has nothing to claim;
    - ``libtpu``: the TPU runtime is importable;
    - ``claim_holders``: (pid, age_s, comm) of OTHER same-uid processes
      holding a TPU device node open -- the claim contention a fresh
      worker would wedge against;
    - ``cause``: the ONE typed pre-spawn verdict: ``tpu_absent`` /
      ``runtime_missing`` / ``claim_held`` / ``ok``.
    """
    import glob
    import importlib.util
    nodes = sorted(glob.glob("/dev/accel*") + glob.glob("/dev/vfio/*"))
    probe = {
        "device_nodes": nodes,
        "libtpu": importlib.util.find_spec("libtpu") is not None,
        "claim_holders": [list(h) for h in _iter_claim_holders(nodes)],
    }
    if not nodes:
        probe["cause"] = "tpu_absent"
    elif not probe["libtpu"]:
        probe["cause"] = "runtime_missing"
    elif probe["claim_holders"]:
        probe["cause"] = "claim_held"
    else:
        probe["cause"] = "ok"
    return probe


def _iter_claim_holders(nodes):
    """(pid, age_s, comm) of OTHER same-uid processes with a TPU device
    node open.  /proc/<pid>/fd scan, same no-psutil discipline as
    _iter_own_workers; unreadable entries are skipped silently."""
    if not nodes:
        return
    me = os.getpid()
    targets = set(nodes)
    try:
        with open("/proc/uptime") as f:
            uptime = float(f.read().split()[0])
        hertz = os.sysconf("SC_CLK_TCK")
    except (OSError, ValueError):
        return
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) == me:
            continue
        try:
            if os.stat(f"/proc/{pid}").st_uid != os.getuid():
                continue
            held = False
            for fd in os.listdir(f"/proc/{pid}/fd"):
                try:
                    if os.readlink(f"/proc/{pid}/fd/{fd}") in targets:
                        held = True
                        break
                except OSError:
                    continue
            if not held:
                continue
            with open(f"/proc/{pid}/comm") as f:
                comm = f.read().strip()
            with open(f"/proc/{pid}/stat") as f:
                stat = f.read()
            start_ticks = int(stat.rsplit(")", 1)[1].split()[19])
            yield int(pid), round(uptime - start_ticks / hertz, 1), comm
        except (OSError, ValueError, IndexError):
            continue


def _kill_claim_holders(probe, max_age_s: float) -> list:
    """The hard-kill half of the wedge fix: a same-uid process that has
    held the device claim longer than any supervision budget is a wedge
    leftover (a previous window's worker, a TPU-initialized pytest), and
    every fresh attempt behind it silently resolves to the CPU fallback.
    Kill it so the retry actually reaches the TPU backend.  Younger
    holders are live neighbours and are left alone (the claim-backoff
    loop handles them)."""
    killed = []
    for pid, age, comm in probe.get("claim_holders", []):
        if age > max_age_s:
            try:
                os.kill(int(pid), signal.SIGKILL)
                killed.append(int(pid))
                _note(f"killed stale claim holder pid {pid} ({comm}, age "
                      f"{age:.0f}s > {max_age_s:.0f}s budget)")
            except OSError:
                pass
    return killed


def _classify_wedge(records, probe) -> str:
    """The ONE typed wedge cause for the bench line, from the pre-spawn
    probe plus which worker stage lines actually arrived:

    - ``tpu_absent``: no TPU device node in this container -- the axon
      plugin has nothing to claim and a 'default' attempt can only ever
      resolve to the CPU host backend (BENCH_r02..: every round since the
      tunnel went away wedged here);
    - ``runtime_missing``: device node present but no libtpu runtime;
    - ``claim_held``: another same-uid process holds the device node;
    - ``backend_init_wedge``: the worker's first-breath spawn line
      arrived but init never did -- wedged inside jax/PJRT backend init
      (the device claim call);
    - ``interpreter_startup_wedge``: not even the spawn line arrived --
      wedged before worker() ran, i.e. inside interpreter startup (the
      site hook importing the PJRT plugin)."""
    if probe.get("cause") != "ok":
        return probe.get("cause", "unknown")
    stages = {r.get("stage") for r in records}
    if "init" in stages:
        return "post_init_wedge"
    if "spawn" in stages:
        return "backend_init_wedge"
    return "interpreter_startup_wedge"


def _claim_like(error: str) -> bool:
    """Does this attempt failure look like device-claim contention (a
    holder that may release) rather than a hard fault?"""
    e = error.lower()
    # Deliberately NOT matching OOM strings ("resource exhausted"): a
    # device OOM is a hard failure for a fixed sweep, not contention.
    return any(s in e for s in (
        "claim", "busy", "already in use", "unavailable",
        "wedged in stage 'spawn'", "wedged in stage 'init'"))


def _tail_line(text: str, limit: int = 240) -> str:
    """Bounded one-line tail of a stderr blob.

    Worker stderr can be multi-KB of XLA/JAX spew; embedding it raw in
    the metric line's note/error fields made the recorded JSON metric
    carry whole wedged-spawn logs (BENCH_r05).  Keep the last few
    non-empty lines, collapsed to one ' / '-joined line, hard-capped at
    ``limit`` characters from the TAIL (the newest text is the
    diagnostic one)."""
    lines = [ln.strip() for ln in text.strip().splitlines() if ln.strip()]
    return _tail_cap(" / ".join(lines[-3:]), limit)


def _tail_cap(text: str, limit: int) -> str:
    """Hard cap keeping the TAIL: in both stderr blobs and multi-attempt
    error joins, the newest text is the diagnostic one."""
    return text if len(text) <= limit else "..." + text[-limit:]


def _harvest_blackbox(proc, dump_dir: str, after: float,
                      wait_s: float = 8.0):
    """SIGUSR1 the wedged child ("give me your blackbox before I kill
    you") and poll for the forensic bundle it dumps; returns the bundle
    path or None.  Best-effort by design: a child wedged inside a C call
    (backend init holding the device claim) cannot run the Python signal
    handler, and that absence is itself recorded in the round artifact."""
    from coast_tpu.obs import flightrec
    try:
        proc.send_signal(signal.SIGUSR1)
    except OSError:
        return None
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        path = flightrec.newest_bundle(dump_dir)
        if path is not None:
            try:
                if os.path.getmtime(path) >= after:
                    return path
            except OSError:
                pass
        if proc.poll() is not None:
            break                    # child died; one last scan below
        time.sleep(0.2)
    path = flightrec.newest_bundle(dump_dir)
    try:
        if path is not None and os.path.getmtime(path) >= after:
            return path
    except OSError:
        pass
    return None


def _attempt(backend: str, timeout_s: int):
    """Run one worker; returns (records, error_note, forensics_path)."""
    env = dict(os.environ)
    # The child's blackbox bundles land where the round artifact can
    # reference them (operator override via COAST_FLIGHTREC_DIR wins).
    dump_dir = env.setdefault("COAST_FLIGHTREC_DIR", os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "artifacts", "flightrec"))
    attempt_t0 = time.time()
    import tempfile
    # Worker stderr goes to a temp file, not a pipe: JAX/XLA on the TPU
    # path can emit more log output than a pipe buffer holds, and an
    # undrained pipe would block the worker mid-measurement.
    err_f = tempfile.TemporaryFile(mode="w+")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker", backend],
        stdout=subprocess.PIPE, stderr=err_f, text=True, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)) or ".")
    _note(f"[{backend}] stage spawn: worker pid {proc.pid} "
          f"(budget {timeout_s}s)")
    records, error, forensics = [], None, None
    deadline = time.monotonic() + timeout_s
    import selectors
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    buf = ""
    stage = "spawn"
    try:
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                error = (f"worker wedged in stage '{stage}' "
                         f"(no progress for {timeout_s}s budget)")
                forensics = _harvest_blackbox(proc, dump_dir, attempt_t0)
                proc.kill()
                break
            if not sel.select(timeout=min(remaining, 5.0)):
                if proc.poll() is not None:
                    break
                continue
            line = proc.stdout.readline()
            if not line:
                break
            buf += line
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            records.append(rec)
            new_stage = rec.get("stage", stage)
            if new_stage != stage or new_stage == "result":
                _note(f"[{backend}] stage {new_stage}"
                      + (f" ({rec.get('kind')})" if rec.get("kind") else ""))
            stage = new_stage
            if stage == "init":
                # Backend is up: grant the full run budget from here.
                deadline = time.monotonic() + RUN_TIMEOUT
            if stage == "done":
                break
        proc.wait(timeout=10)
    except Exception as e:  # noqa: BLE001 - supervision must not raise
        error = error or f"supervisor error: {type(e).__name__}: {e}"
        proc.kill()
    finally:
        try:
            err_f.seek(0)
            stderr_tail = err_f.read()[-2000:]
            err_f.close()
        except Exception:  # noqa: BLE001
            stderr_tail = ""
        sel.close()
    if proc.returncode not in (0, None) and error is None:
        error = (f"worker exited rc={proc.returncode} in stage '{stage}': "
                 + _tail_line(stderr_tail, 160) if stderr_tail.strip()
                 else f"worker exited rc={proc.returncode}")
    if error and stderr_tail.strip():
        error += " | stderr: " + _tail_line(stderr_tail)
    return records, error, forensics


def _summarize(records):
    thr = [r for r in records if r.get("kind") == "throughput"]
    ovh = [r for r in records if r.get("kind") == "overhead"]
    flag = [r for r in records if r.get("kind") == "flagship"]
    init = next((r for r in records if r.get("stage") == "init"), None)
    out = {}
    if init:
        out["backend"] = init.get("backend")
        out["devices"] = init.get("devices")
    if ovh:
        out["overhead"] = {k: v for k, v in ovh[-1].items()
                           if k not in ("stage", "kind")}
    if flag:
        out["flagship"] = [{k: v for k, v in r.items()
                            if k not in ("stage", "kind")} for r in flag]
    if thr:
        best = max(thr, key=lambda r: r["injections_per_sec"])
        out["throughput"] = [
            {k: r[k] for k in ("batch_size", "injections",
                               "seconds", "injections_per_sec")}
            for r in thr]
        out["best"] = best
    return out


def main() -> int:
    if len(sys.argv) >= 2 and sys.argv[1] == "--worker":
        worker(sys.argv[2] if len(sys.argv) > 2 else "default")
        return 0

    errors = []
    # A wedged worker from an earlier window holds the device claim and
    # silently turns every new run into the CPU fallback -- clear it first.
    stale_budget = INIT_TIMEOUT + RUN_TIMEOUT + 120
    _kill_stale_workers(stale_budget)
    # Pre-spawn environment probe (the spawn-wedge fix): learn BEFORE
    # burning an INIT_TIMEOUT whether a TPU attempt can possibly succeed,
    # and hard-kill any stale same-uid claim holder so a retry actually
    # reaches the backend instead of wedging behind the corpse.
    probe = _probe_env()
    if _kill_claim_holders(probe, stale_budget):
        time.sleep(2.0)
        probe = _probe_env()
    _note(f"env probe: cause={probe['cause']} "
          f"nodes={len(probe['device_nodes'])} libtpu={probe['libtpu']} "
          f"holders={len(probe['claim_holders'])}")
    force = os.environ.get("COAST_BENCH_BACKEND")  # e.g. "cpu" for dev boxes
    if force:
        plan = [(force, INIT_TIMEOUT)]
    elif probe["cause"] == "tpu_absent":
        # No device node behind this container: a 'default' retry can
        # never reach hardware, so don't churn the retry budget against
        # it -- one default attempt (fast host resolve), then the
        # explicit fallback; the typed cause rides the bench line.
        plan = [("default", INIT_TIMEOUT), ("cpu", RETRY_TIMEOUT)]
    else:
        plan = [("default", INIT_TIMEOUT), ("default", RETRY_TIMEOUT),
                ("cpu", RETRY_TIMEOUT)]
    summary, used = {}, None
    spawn_wedge = None
    wedge_forensics = None
    wedge_cause = None
    last_tpu_records = []
    for backend, budget in plan:
        claim_tries = 0
        claim_t0 = time.monotonic()
        while True:
            t0 = time.time()
            records, error, forensics = _attempt(backend, budget)
            if forensics:
                # Keep the NEWEST wedge bundle: repeated claim-retries
                # each harvest one, and the last is the give-up evidence.
                wedge_forensics = forensics
                _note(f"[{backend}] harvested worker blackbox: "
                      f"{forensics}")
            if error:
                errors.append(
                    f"[{backend} attempt, {time.time()-t0:.0f}s] {error}")
            if backend != "cpu":
                last_tpu_records = records
            summary = _summarize(records)
            if "best" in summary:
                used = backend
                break
            # Claim contention on a real-hardware attempt: back off and
            # retry the SAME backend before falling through the plan --
            # the holder (another poller window, a neighbour) typically
            # releases within a minute.  Bounded by retries AND total
            # wall clock; exhausting either yields one explicit
            # spawn-wedge diagnosis instead of silent fallthrough.
            if backend != "cpu" and error and _claim_like(error):
                elapsed = time.monotonic() - claim_t0
                if claim_tries >= CLAIM_RETRIES or elapsed > CLAIM_TOTAL_S:
                    wedge_cause = _classify_wedge(records, probe)
                    spawn_wedge = (
                        f"{backend} spawn wedged ({wedge_cause}): gave up "
                        f"after {claim_tries + 1} attempt(s) / {elapsed:.0f}s "
                        f"(budget {CLAIM_RETRIES + 1} x {CLAIM_TOTAL_S:.0f}s)"
                        f"; last: {_tail_cap(error, 160)}")
                    _note(spawn_wedge)
                    break
                delay = CLAIM_BACKOFF_S * (2 ** claim_tries)
                claim_tries += 1
                _note(f"[{backend}] claim-like failure; backoff {delay:.0f}s "
                      f"then retry {claim_tries}/{CLAIM_RETRIES}")
                time.sleep(delay)
                _kill_stale_workers(stale_budget)
                # Re-probe between retries: the holder the backoff waited
                # out may now be stale enough to hard-kill.
                _kill_claim_holders(_probe_env(), stale_budget)
                continue
            break
        if "best" in summary:
            break
    if spawn_wedge and summary.get("backend") not in (None, "cpu"):
        # A later attempt DID measure on hardware: the give-up diagnosis
        # belongs to a transient, not to this record.
        _note(f"spawn-wedge cleared: a later attempt measured on "
              f"{summary.get('backend')}")
        spawn_wedge = None
        wedge_forensics = None
        wedge_cause = None

    artifacts_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "artifacts")
    full_path = os.path.join(artifacts_dir, "bench_full.json")
    if "best" in summary:
        value = summary["best"]["injections_per_sec"]
        full = {
            "metric": "mm_tmr_fault_injections_per_sec",
            "value": value,
            "unit": "injections/sec",
            "vs_baseline": round(value / BASELINE_INJ_PER_SEC, 2),
            "backend": summary.get("backend"),
            "devices": summary.get("devices"),
            "throughput": summary.get("throughput"),
            "overhead": summary.get("overhead"),
            "flagship": summary.get("flagship"),
        }
        if errors:
            # Per-attempt notes are already one bounded line each; cap
            # the join too (tail side: the newest attempt's failure is
            # the one worth keeping) so the artifact's error field stays
            # a summary, never a log dump.
            full["error"] = _tail_cap("; ".join(errors), 900)
        if spawn_wedge:
            # The give-up diagnosis plus the wedged child's blackbox
            # bundle (obs/flightrec.py): forensics is None when the
            # child could not answer SIGUSR1 (wedged in a C call).
            full["spawn_wedge"] = {"note": spawn_wedge,
                                   "cause": wedge_cause,
                                   "probe": probe,
                                   "forensics": wedge_forensics}
        # One predicate for "this ran on the host": the worker-REPORTED
        # backend, not the attempt label -- a "default" attempt on a
        # TPU-less box silently resolves to CPU and must carry the same
        # caveat as the explicit fallback.
        on_cpu = (summary.get("backend") == "cpu")
        if on_cpu and not force:
            full["note"] = ("TPU backend unreachable; value measured on the "
                            "CPU fallback backend")
            # The typed WHY behind the fallback (the spawn-wedge fix's
            # contract: never a silent CPU record): the pre-spawn probe's
            # verdict, refined by which worker stage lines the last
            # hardware attempt actually produced.
            full["tpu_diagnosis"] = {
                "cause": wedge_cause or _classify_wedge(last_tpu_records,
                                                        probe),
                "probe": probe}
        # Per-backend trajectory: this round's value is compared against
        # (and then refreshes) ITS OWN backend's last record, so a
        # CPU-fallback round never reads as a regression from -- or an
        # improvement over -- an on-chip number.
        prev = _load_backend_baseline(summary.get("backend"))
        if prev and prev.get("value"):
            full["backend_baseline"] = prev
            full["vs_backend_baseline"] = round(value / prev["value"], 3)
        if on_cpu:
            # Never let a fallback record silently replace the hardware
            # story: embed the last on-chip measurement alongside it.
            try:
                with open(LAST_TPU_RECORD) as f:
                    full["last_known_tpu"] = json.load(f)
            except (OSError, ValueError):
                pass
        elif summary.get("backend"):
            # A definite non-CPU backend measured this: it becomes the new
            # last-known on-chip record.  backend-unknown records (init
            # line never arrived) are saved nowhere.
            try:
                os.makedirs(os.path.dirname(LAST_TPU_RECORD), exist_ok=True)
                with open(LAST_TPU_RECORD, "w") as f:
                    json.dump({"measured_at": time.strftime("%Y-%m-%d %H:%M"),
                               "record": full}, f, indent=1)
            except OSError:
                pass
        if summary.get("backend"):
            try:
                with open(_backend_record_path(summary["backend"]),
                          "w") as f:
                    json.dump({"measured_at": time.strftime("%Y-%m-%d %H:%M"),
                               "record": full}, f, indent=1)
            except OSError:
                pass
        try:
            os.makedirs(artifacts_dir, exist_ok=True)
            with open(full_path, "w") as f:
                json.dump(full, f, indent=1)
        except OSError:
            pass
        # The one printed line stays compact (the driver tail-captures it);
        # bulk lives in the artifact.
        line = {k: full.get(k) for k in
                ("metric", "value", "unit", "vs_baseline", "backend")}
        frac = None
        for fl in (summary.get("flagship") or []):
            cands = [fl.get("fraction_of_peak")] + [
                c.get("fraction_of_peak") for c in fl.get("campaign", [])]
            for c in cands:
                if c is not None and (frac is None or c > frac):
                    frac = c
        if frac is not None:
            line["flagship_fraction_of_peak"] = frac
        if "vs_backend_baseline" in full:
            line["vs_backend_baseline"] = full["vs_backend_baseline"]
        if "note" in full:
            line["note"] = full["note"]
        if "tpu_diagnosis" in full:
            # Compact on the line (cause only); the probe detail lives in
            # the artifact.
            line["tpu_diagnosis"] = full["tpu_diagnosis"]["cause"]
        if spawn_wedge:
            line["spawn_wedge"] = {"note": spawn_wedge,
                                   "cause": wedge_cause,
                                   "forensics": wedge_forensics}
        if errors:
            line["error"] = _tail_cap("; ".join(errors), 300)
        line["artifact"] = "artifacts/bench_full.json"
        print(json.dumps(line))
        for e in errors:
            print(f"# {e}", file=sys.stderr)
        return 0
    line = {"metric": "mm_tmr_fault_injections_per_sec"}
    # No measurement anywhere: still one parseable JSON line, nonzero rc.
    line.update({"value": None, "unit": "injections/sec", "vs_baseline": None,
                 "error": (_tail_cap("; ".join(errors), 900)
                           or "no measurement produced"),
                 "partial": summary or None})
    if spawn_wedge:
        line["spawn_wedge"] = {"note": spawn_wedge,
                               "cause": wedge_cause,
                               "probe": probe,
                               "forensics": wedge_forensics}
    print(json.dumps(line))
    for e in errors:
        print(f"# {e}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
