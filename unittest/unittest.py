#!/usr/bin/env python3
"""Entry-point shim keeping the reference's harness layout
(unittest/unittest.py cfg/fast.yml); the implementation lives in
coast_tpu.testing.harness."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from coast_tpu.testing.harness import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
