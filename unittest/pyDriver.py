#!/usr/bin/env python3
"""Driver-of-drivers shim (reference: unittest/pyDriver.py runs specialized
drivers like llvm-stress over pass combos, regex 'Success!').  The yml
``drivers:`` section of coast_tpu.testing.harness is the implementation."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from coast_tpu.testing.harness import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:] or ["unittest/cfg/regression.yml"]))
