#!/bin/sh
# RTOS smoke tier: the unittest/rtos_test.sh analogue (:26-44).
#
# The reference builds every FreeRTOS target and boots each in QEMU for a
# few seconds (kill-and-hope, no output oracle).  Our rtos_app targets
# run to completion with a real oracle, so this tier is strictly
# stronger: build + run each protected target under the canonical
# production scope config and require the golden-clean UART line.
set -e
cd "$(dirname "$0")/.."

for tgt in rtos_app rtos_app_dwc rtos_mm rtos_mm_dwc rtos_kUser rtos_kUser_dwc; do
    echo "== rtos smoke: $tgt"
    out=$(timeout 600 make -s -C rtos "$tgt")
    echo "$out" | tail -1
    echo "$out" | grep -q "C: 0 E: 0" || { echo "FAIL: $tgt"; exit 1; }
done
echo "Success!"
